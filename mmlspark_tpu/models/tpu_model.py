"""TPUModel — batched DNN inference over tables.

TPU-native analog of the reference's CNTKModel
(ref: src/cntk-model/src/main/scala/CNTKModel.scala:147-514):
where the reference broadcasts a serialized CNTK graph to executors,
clones it per partition with shared weights, and feeds minibatched rows
through JNI (``CNTKModelUtils.applyModel``/``applyCNTKFunction``
:30-140), we hold a JAX apply function + weights pytree, jit it once per
(batch-shape, dtype), shard the batch over the mesh's data axis, and let
XLA run the whole minibatch on the MXU. ``feedDict``/``fetchDict``
multi-input/output maps follow CNTKModel.scala:206-225; input coercion
(float/double/vector) follows :419-462.

The weights are device-resident and replicated across the mesh — the
analog of the reference's broadcast + ``ParameterCloningMethod.Share``
(:83) without any copy per partition.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.core.metrics import histogram_set
from mmlspark_tpu.core.params import (
    DictParam, EnumParam, HasInputCol, HasOutputCol, IntParam, PyTreeParam,
    StringParam, UDFParam,
)
from mmlspark_tpu.core.schema import Field, ImageSchema, Schema, TENSOR, VECTOR
from mmlspark_tpu.core.stage import Model
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.parallel import mesh as mesh_lib

# smallest serving shape bucket: ragged micro-batches pad UP to the next
# power of two from here, so the compiled-executable set stays
# log2(batchSize)-sized (see TPUModel.bucket_sizes)
MIN_BUCKET = 8


def _column_to_array(col, field: Field, dtype) -> np.ndarray:
    """Coerce a table column into a dense batch array
    (ref: CNTKModel.scala:419-462 coerceDFAndFeedDict)."""
    if field is not None and ImageSchema.is_image(field):
        return np.stack([np.asarray(r[ImageSchema.DATA]) for r in col]
                        ).astype(dtype)
    if isinstance(col, np.ndarray):
        return np.asarray(col, dtype=dtype)
    first = next((x for x in col if x is not None), None)
    if isinstance(first, np.ndarray):
        return np.stack([np.asarray(x) for x in col]).astype(dtype)
    return np.asarray(col, dtype=dtype)


class TPUModel(Model, HasInputCol, HasOutputCol):
    """Run a jitted forward function over a table, minibatched + sharded.

    The model is ``model_fn(weights, inputs: dict[str, Array]) ->
    dict[str, Array] | Array``. Use ``from_flax`` / ``from_fn`` to build.
    """

    modelFn = UDFParam("callable (weights, inputs dict) -> outputs", default=None)
    weights = PyTreeParam("model weights pytree", default=None)
    feedDict = DictParam(
        "map model input name -> table column "
        "(ref: CNTKModel feedDict :206)", default=None)
    fetchDict = DictParam(
        "map output column -> model output name "
        "(ref: CNTKModel fetchDict :217)", default=None)
    batchSize = IntParam("minibatch size", default=64)
    # float64 deliberately absent: JAX canonicalizes f64->f32 unless the
    # global jax_enable_x64 flag is on, which we don't silently toggle
    computeDtype = EnumParam(["float32", "bfloat16"],
                             "on-device compute dtype", default="float32")
    # serving precision label: 'int8' models carry per-channel-quantized
    # Dense weights + calibrated activation scales in the weights tree
    # (core/quantize.py) and run int8xint8->i32 matmuls with f32 dequant
    # epilogues. Set by quantize(), surfaced on /healthz + /metrics.
    precision = EnumParam(["f32", "int8"],
                          "inference precision (set by quantize())",
                          default="f32")

    def _post_init(self):
        self._mesh: Optional[Mesh] = None
        # explicit mesh sharding (set_sharding / serving/sharded.py):
        # when set, the forward jits with DECLARED in_shardings/
        # out_shardings (weights per their spec tree — sharded weights
        # are how a model too big for one device serves from the mesh —
        # inputs/outputs per in_spec/out_spec) instead of the
        # replicate-weights + shard-batch default
        self._sharding: Optional[Dict[str, Any]] = None
        # True on models rebuilt from an AOT artifact (serving/aot.py);
        # exported as the serving_model_info 'aot' label
        self.aot = False
        self._jitted: Dict[Tuple, Callable] = {}
        self._device_weights = None
        # lazy init is shared mutable state; concurrent first calls
        # (multi-worker serving engines) must not race it — a race would
        # device_put N transient copies of the full weight tree
        import threading
        self._init_lock = threading.Lock()
        # one increment per jit TRACE of the forward (== one XLA compile
        # per distinct bucket shape/dtype): the recompile-guard signal
        # for steady-state serving. Lock-guarded — concurrent worker
        # threads can first-trace two buckets at once, and a bare +=
        # is a read-modify-write that could drop a count.
        self.jit_cache_misses = 0
        self._miss_lock = threading.Lock()
        # serving-path breakdown: host batch assembly + padding vs the
        # device dispatch->readback round trip (exported through
        # ServingEngine /healthz via the duck-typed .metrics hook)
        self._hists = histogram_set("pad_ms", "device_ms")

    def _on_param_change(self, name: str) -> None:
        if name == "weights":
            self._device_weights = None
        elif name == "modelFn":
            self._jitted = {}

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_fn(fn: Callable, weights: Any, **kw) -> "TPUModel":
        return TPUModel(modelFn=fn, weights=weights, **kw)

    @staticmethod
    def from_flax(module, variables: Any, method=None, **kw) -> "TPUModel":
        """Wrap a flax module; inputs dict values are passed positionally
        in feedDict order (single input the common case). ``variables`` is
        the full init() result — every collection (params, batch_stats, …)
        is kept so BatchNorm-style models work at inference."""
        fn = _FlaxApply(module, method)
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        return TPUModel(modelFn=fn, weights=dict(variables), **kw)

    # -- mesh / jit management ----------------------------------------------

    def set_mesh(self, mesh: Optional[Mesh]) -> "TPUModel":
        self._mesh = mesh
        self._jitted = {}
        self._device_weights = None
        return self

    def set_sharding(self, mesh: Mesh, weight_specs: Any = None,
                     in_spec: Optional[P] = None,
                     out_spec: Optional[P] = None) -> "TPUModel":
        """Mesh-shard this model's serving program (the pjit pattern:
        jit with explicit ``in_shardings``/``out_shardings`` over a
        named mesh; GSPMD, Xu et al. 2021 / Pope et al. 2022).

        - ``weight_specs``: a ``PartitionSpec``, a pytree of specs
          matching the weights, or a callable ``(path, leaf) -> spec``
          (see ``serving.sharded.auto_weight_specs``). Default:
          replicated. Sharded weight leaves are how a model whose
          weights exceed one device's memory serves from the mesh —
          per-device resident bytes stay below the total.
        - ``in_spec``: placement of every model input (default:
          batch-dim over ``'data'`` when the mesh has that axis, else
          replicated). A seq-sharded LM passes ``P(None, 'seq')``.
        - ``out_spec``: placement of every output (default =
          ``in_spec``); the readback gathers.

        Shardings here are declared, never inferred (audited by
        tools/check_fusion_kernels.py ``check_sharded_serving``)."""
        if in_spec is None:
            in_spec = P("data") if "data" in mesh.shape else P()
        if out_spec is None:
            out_spec = in_spec
        weights = self.get("weights")
        if weight_specs is None:
            weight_specs = P()
        if callable(weight_specs) and not isinstance(weight_specs, P):
            flat, treedef = jax.tree_util.tree_flatten_with_path(weights)
            specs = jax.tree_util.tree_unflatten(
                treedef, [weight_specs(jax.tree_util.keystr(path), leaf)
                          for path, leaf in flat])
        elif isinstance(weight_specs, P):
            specs = jax.tree_util.tree_map(lambda _: weight_specs,
                                           weights)
        else:
            specs = weight_specs   # a full pytree of PartitionSpecs
        w_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        # batch-dim input sharding needs EVERY serving bucket (pow-2
        # from MIN_BUCKET up to batchSize) to divide the axis — refuse
        # now with the fix, not at the first small live batch that
        # buckets to 8 rows over a non-pow-2 axis
        if in_spec and in_spec[0] is not None:
            n = int(mesh.shape[in_spec[0]])
            if MIN_BUCKET % n:
                raise ValueError(
                    f"the {in_spec[0]!r} axis has {n} shards, which "
                    f"does not divide the smallest serving bucket "
                    f"({MIN_BUCKET}): small micro-batches could never "
                    f"shard")
            if int(self.get("batchSize")) % n:
                raise ValueError(
                    f"batchSize {self.get('batchSize')} does not divide "
                    f"the {in_spec[0]!r} axis ({n} shards); pick a "
                    f"multiple of {n}")
        self._sharding = {
            "mesh": mesh,
            "weight_specs": specs,
            "weight_shardings": w_shardings,
            "in": NamedSharding(mesh, in_spec),
            "in_spec": in_spec,
            "out": NamedSharding(mesh, out_spec),
            "out_spec": out_spec,
        }
        self._mesh = mesh
        self._jitted = {}
        self._device_weights = None
        return self

    @property
    def sharding(self) -> Optional[Dict[str, Any]]:
        return self._sharding

    def _get_mesh(self) -> Mesh:
        if self._mesh is None:
            with self._init_lock:
                if self._mesh is None:
                    self._mesh = mesh_lib.make_mesh()
        return self._mesh

    def _weights_on_device(self):
        """Replicate weights across the mesh once (broadcast analog,
        ref: CNTKModel.scala:413 rebroadcastCNTKModel). Double-checked
        locking: thread-safe under multi-worker serving."""
        if self._device_weights is None:
            m = self._get_mesh()
            with self._init_lock:
                if self._device_weights is None:
                    if self._sharding is not None:
                        # per-leaf declared placement: sharded leaves
                        # land split across the mesh (per-device
                        # resident bytes < the total weight bytes)
                        self._device_weights = jax.tree_util.tree_map(
                            lambda a, s: jax.device_put(
                                jnp.asarray(a), s),
                            self.get("weights"),
                            self._sharding["weight_shardings"])
                    else:
                        repl = NamedSharding(m, P())
                        self._device_weights = jax.tree_util.tree_map(
                            lambda a: jax.device_put(jnp.asarray(a),
                                                     repl),
                            self.get("weights"))
        return self._device_weights

    def resident_bytes(self) -> int:
        """Device bytes the shipped weights occupy, summed across PER-
        DEVICE shards over the whole mesh (a replicated tree counts
        once per device; a sharded tree counts its true split
        footprint) — the zoo's per-model eviction-cost signal. Falls
        back to the host estimate before the first ship."""
        dev = self._device_weights
        if dev is not None:
            from mmlspark_tpu.core.fusion import _shard_bytes
            return sum(_shard_bytes(leaf)
                       for leaf in jax.tree_util.tree_leaves(dev))
        host = self.get("weights")
        if host is None:
            return 0
        return int(sum(int(np.asarray(a).nbytes)
                       for a in jax.tree_util.tree_leaves(host)))

    def _feeds(self) -> Dict[str, str]:
        fd = self.get("feedDict")
        if fd:
            return dict(fd)
        return {"input": self.get_input_col()}

    def _fetches(self) -> Dict[str, str]:
        fd = self.get("fetchDict")
        if fd:
            return dict(fd)
        return {self.get_output_col(): "output"}

    def _compiled(self) -> Callable:
        """One jit wrapper per model (jax.jit handles per-shape retraces
        internally, one executable per bucket shape); invalidated when
        modelFn changes. Every trace — i.e. every compile-cache miss —
        bumps ``jit_cache_misses``, and the padded input buffers are
        DONATED on accelerator backends (a serving batch is consumed
        exactly once, so XLA may alias it for activations instead of
        holding both live in HBM)."""
        fn = self._jitted.get("run")
        if fn is None:
            with self._init_lock:
                fn = self._jitted.get("run")
                if fn is None:
                    model_fn = self.get("modelFn")
                    model = self

                    def run(weights, inputs: Dict[str, jnp.ndarray]):
                        # trace-time side effect: runs once per distinct
                        # input signature, i.e. once per XLA compile
                        with model._miss_lock:
                            model.jit_cache_misses += 1
                        out = model_fn(weights, inputs)
                        if not isinstance(out, dict):
                            out = {"output": out}
                        return out

                    # CPU's donation support is backend-version dependent
                    # and only emits warnings there; donate where it pays
                    donate = (1,) if jax.default_backend() not in ("cpu",) \
                        else ()
                    if self._sharding is not None:
                        fn = self._jit_sharded(run, donate)
                    else:
                        fn = jax.jit(run, donate_argnums=donate)
                    self._jitted["run"] = fn
        return fn

    def _jit_sharded(self, run: Callable, donate: Tuple[int, ...],
                     ) -> Callable:
        """The mesh-sharded forward: jit with EXPLICIT in_shardings
        (the per-leaf weight placement + the declared input spec for
        every feed) and out_shardings, input buffers donated — never
        inferred shardings (the sharded-serving audit contract)."""
        sh = self._sharding
        return jax.jit(
            run,
            in_shardings=(sh["weight_shardings"], sh["in"]),
            out_shardings=sh["out"],
            donate_argnums=donate)

    # -- serving shape buckets ----------------------------------------------

    def bucket_sizes(self) -> List[int]:
        """The padded batch-row sizes serving traffic compiles for:
        powers of two from MIN_BUCKET up, capped by (and always
        including) batchSize. Ragged micro-batches pad UP to the nearest
        bucket, bounding the distinct compiled shapes to
        log2(batchSize)+O(1) regardless of traffic mix."""
        cap = int(self.get("batchSize"))
        sizes: List[int] = []
        b = MIN_BUCKET
        while b < cap:
            sizes.append(b)
            b *= 2
        sizes.append(cap)
        return sizes

    def warmup(self, example, sizes: Optional[List[int]] = None) -> int:
        """Pre-compile every serving bucket so no live request ever pays
        an XLA compile (bounded first-request latency — the explicit
        warmup hook of the serving hot path).

        ``example`` is a DataTable, or a dict of column -> array,
        holding at least one representative row for every feed column.
        Rows are tiled up to each bucket size and pushed through
        ``transform`` (core/warmup.py — each bucket's compile wall
        lands in the ``model_warmup_ms`` histogram on /metrics).
        Returns the number of compiles triggered (0 when everything was
        already warm)."""
        from mmlspark_tpu.core.warmup import warmup_transform
        return warmup_transform(self, example, sizes)

    def bucket_for(self, rows: int) -> int:
        """The padded bucket a ``rows``-row micro-batch compiles/runs
        at (the pow-2 padding rule of ``bucket_sizes``): serving spans
        annotate it so a trace shows which executable a batch hit."""
        cap = int(self.get("batchSize"))
        b = MIN_BUCKET
        while b < rows:
            b *= 2
        return min(b, cap)

    def histograms(self) -> Dict[str, Any]:
        """Raw pad/device histogram objects (exact buckets) for the
        Prometheus exposition — ``metrics()`` keeps returning the
        summary view."""
        return dict(self._hists)

    def metrics(self) -> Dict[str, Any]:
        """Serving instrumentation: pad/device latency summaries + the
        compile-cache miss counter (duck-typed hook consumed by
        ServingEngine's /healthz export)."""
        out: Dict[str, Any] = {k: h.summary()
                               for k, h in self._hists.items()}
        out["jit_cache_misses"] = self.jit_cache_misses
        out["precision"] = self.get("precision")
        out["aot"] = bool(self.aot)
        if self._sharding is not None:
            out["sharded"] = True
            out["mesh"] = dict(self._sharding["mesh"].shape)
            out["in_spec"] = str(self._sharding["in_spec"])
        return out

    # -- post-training quantization -----------------------------------------

    def quantize(self, calib, percentile: float = 100.0) -> "TPUModel":
        """Int8 post-training quantization (core/quantize.py): calibrate
        per-tensor activation clip ranges on the ``calib`` rows (a
        DataTable or column->array dict holding a held-out batch for
        every feed column), quantize every Dense kernel per-channel, and
        return a NEW ``TPUModel`` whose forward runs int8xint8->i32
        matmuls with f32 dequant epilogues. This model (the f32 path) is
        untouched — it stays the accuracy oracle and the swap-rollback
        target. The returned model keeps the full serving discipline
        (pow-2 buckets, ``warmup()``, ``jit_cache_misses``, donation)
        and labels itself ``precision='int8'`` on /healthz.

        Requires a flax-module model (``from_flax`` or any modelFn
        exposing ``.module``): quantization intercepts ``nn.Dense``
        calls; conv/LSTM/embedding layers stay f32 by design."""
        from mmlspark_tpu.core import quantize as QZ
        model_fn = self.get("modelFn")
        module = getattr(model_fn, "module", None)
        if module is None:
            raise ValueError(
                "quantize() needs a flax-module model (TPUModel.from_flax"
                " or a modelFn exposing .module); arbitrary callables "
                "cannot be post-training quantized")
        table = calib if isinstance(calib, DataTable) \
            else DataTable(dict(calib))
        if len(table) == 0:
            raise ValueError("quantize needs at least one calibration row")
        int_input = bool(getattr(model_fn, "int_input", False))
        host_dtype = np.int32 if int_input else np.float32
        args = []
        for _model_in, col in self._feeds().items():
            args.append(_column_to_array(table[col],
                                         table.schema.get(col),
                                         host_dtype))
        variables = self.get("weights")
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        qfn, qweights = QZ.quantize_flax(
            module, variables, args,
            method=getattr(model_fn, "method", None),
            percentile=percentile)
        # computeDtype pins to float32: the dequant epilogue contract is
        # f32, and routing int8 dequant through bf16 would stack a
        # second rounding on top of the quantization error
        return TPUModel(modelFn=qfn, weights=qweights,
                        feedDict=self.get("feedDict"),
                        fetchDict=self.get("fetchDict"),
                        batchSize=self.get("batchSize"),
                        computeDtype="float32",
                        inputCol=self.get("inputCol"),
                        outputCol=self.get("outputCol"),
                        precision="int8")

    # -- fusion hook ---------------------------------------------------------

    def reads_columns(self, schema):
        return list(self._feeds().values())

    def writes_columns(self, schema):
        return list(self._fetches().keys())

    def device_op(self, schema):
        """Fusion hook (core/fusion.py): the forward becomes one op in a
        fused pipeline program — upstream featurization flows into it
        on-device, its own minibatch/bucket machinery is bypassed (the
        fused plan owns batching). Integer-token models feed through an
        i32 Feed so ids never round-trip through float."""
        from mmlspark_tpu.core import fusion as FZ
        feeds_map = self._feeds()
        fetches = self._fetches()
        model_fn = self.get("modelFn")
        if model_fn is None:
            return None
        bf16 = self.get("computeDtype") == "bfloat16"
        int_input = bool(getattr(model_fn, "int_input", False))
        reads: List[str] = []
        op_feeds: List[Any] = []
        env_key: Dict[str, str] = {}
        for model_in, col in feeds_map.items():
            if int_input:
                name = f"{self.uid}:{col}:i32"
                op_feeds.append(FZ.Feed(
                    name, lambda t, _c=col: _column_to_array(
                        t[_c], t.schema.get(_c), np.int32)))
                env_key[model_in] = name
            else:
                reads.append(col)
                env_key[model_in] = col

        def fn(consts, env, _keys=tuple(env_key.items()),
               _fetch=tuple(fetches.items()), _bf16=bf16,
               _int=int_input):
            inputs = {}
            for model_in, key in _keys:
                x = env[key]
                if _bf16 and not _int:
                    x = x.astype(jnp.bfloat16)
                inputs[model_in] = x
            out = model_fn(consts, inputs)
            if not isinstance(out, dict):
                out = {"output": out}
            res = {}
            for out_col, model_out in _fetch:
                val = out[model_out]
                if val.dtype == jnp.bfloat16:
                    val = val.astype(jnp.float32)
                res[out_col] = val
            return res

        return FZ.DeviceOp(
            self, reads=reads, writes=list(fetches.keys()), fn=fn,
            make_consts=lambda: self.get("weights"), feeds=op_feeds,
            name=(f"{type(self).__name__}:{self.uid}:int8"
                  if self.get("precision") == "int8" else None))

    # -- transform ----------------------------------------------------------

    def transform(self, table: DataTable) -> DataTable:
        feeds = self._feeds()
        fetches = self._fetches()
        dtype = np.dtype(self.get("computeDtype")) \
            if self.get("computeDtype") != "bfloat16" else jnp.bfloat16
        batch_size = self.get("batchSize")
        mesh = self._get_mesh()
        weights = self._weights_on_device()

        n = len(table)
        out_cols: Dict[str, List[np.ndarray]] = {c: [] for c in fetches}

        # integer-token models (BiLSTM/Transformer) must not round-trip
        # their ids through float compute dtypes
        int_input = bool(getattr(self.get("modelFn"), "int_input", False))

        import time as _time

        def _bucket(rows: int) -> int:
            """Pad partial batches up to a power-of-two row count (capped
            at batchSize): the jitted forward is shape-keyed, so ragged
            batch sizes — serving micro-batches drain whatever is queued
            — would each trigger a fresh XLA compile (seconds through a
            remote backend). Buckets bound the distinct shapes to
            log2(batchSize)+1 (see bucket_sizes/bucket_for); padded rows
            are sliced off by the [:true_len] readback."""
            b = MIN_BUCKET
            while b < rows:
                b *= 2
            return min(b, batch_size)

        def prepare(start):
            """Host batch assembly + device_put — runs on the prefetch
            thread so transfers overlap the current batch's compute
            (the host-bound loop VERDICT flagged in :168-190)."""
            t0 = _time.perf_counter()
            stop = min(start + batch_size, n)
            rows = stop - start
            bucket = _bucket(rows)
            inputs = {}
            for model_in, col_name in feeds.items():
                field = table.schema.get(col_name)
                arr = table[col_name][start:stop]
                host_dtype = np.int32 if int_input else (
                    np.float32 if dtype == jnp.bfloat16 else dtype)
                arr = _column_to_array(arr, field, host_dtype)
                if bucket > rows:
                    # edge-pad (pad_to_multiple's discipline): padded
                    # rows stay VALID inputs, so models with log/1-over/
                    # normalization paths can't turn them into NaNs that
                    # a cross-row computation would spread to real rows
                    arr, _ = mesh_lib.pad_to_multiple(arr, bucket, axis=0)
                if self._sharding is not None:
                    # ship straight into the DECLARED input placement
                    # (replicated for tensor parallelism, seq-sharded
                    # for the ring-attention LM, batch-sharded for DP)
                    # so the sharded executable never reshuffles inputs
                    sharded = jax.device_put(arr, self._sharding["in"])
                else:
                    sharded, _ = mesh_lib.shard_batch(mesh, arr)
                if dtype == jnp.bfloat16 and not int_input:
                    sharded = sharded.astype(jnp.bfloat16)
                inputs[model_in] = sharded
            self._hists["pad_ms"].observe(
                (_time.perf_counter() - t0) * 1e3)
            return rows, inputs

        def flush(item):
            true_len, outputs, t_dispatch = item
            for out_col, model_out in fetches.items():
                val = np.asarray(outputs[model_out].astype(jnp.float32)
                                 if outputs[model_out].dtype == jnp.bfloat16
                                 else outputs[model_out])
                out_cols[out_col].append(val[:true_len])
            # dispatch -> readback-complete: the device round trip as
            # the serving path experiences it (async dispatch means the
            # compiled call alone measures nothing)
            self._hists["device_ms"].observe(
                (_time.perf_counter() - t_dispatch) * 1e3)

        def dispatch(inputs):
            outputs = self._compiled()(weights, inputs)
            for model_out in fetches.values():
                if model_out not in outputs:
                    raise KeyError(
                        f"model output {model_out!r} not in outputs "
                        f"{list(outputs)}")
            return outputs

        if 0 < n <= batch_size:
            # serving fast path: one micro-batch — prepare, dispatch,
            # read back inline. The prefetcher buys nothing here and
            # costs a thread spawn + queue handshake per request batch
            # on accelerator backends.
            true_len, inputs = prepare(0)
            t_dispatch = _time.perf_counter()
            flush((true_len, dispatch(inputs), t_dispatch))
        else:
            from mmlspark_tpu.utils.prefetch import make_prefetcher
            feed = make_prefetcher(iter(range(0, n, batch_size)), prepare,
                                   depth=2)
            pending: List[Tuple[int, Dict[str, jnp.ndarray], float]] = []
            try:
                for true_len, inputs in feed:
                    t_dispatch = _time.perf_counter()
                    pending.append((true_len, dispatch(inputs),
                                    t_dispatch))
                    if len(pending) > 1:
                        # delayed-by-one readback: batch k's D2H happens
                        # while batch k+1 runs on device
                        flush(pending.pop(0))
            finally:
                feed.close()
            for item in pending:
                flush(item)

        result = table
        for out_col, parts in out_cols.items():
            merged = np.concatenate(parts, axis=0) if parts else np.empty((0,))
            tag = VECTOR if merged.ndim == 2 else TENSOR if merged.ndim > 2 \
                else Field(out_col, "f32").tag
            result = result.with_column(out_col, merged, Field(out_col, tag))
        return result

    def transform_schema(self, schema: Schema) -> Schema:
        for col_name in self._feeds().values():
            schema.require(col_name)
        out = schema
        for out_col in self._fetches():
            out = out.add_or_replace(Field(out_col, VECTOR))
        return out


class _FlaxApply:
    """Picklable flax apply wrapper (module defs pickle by value of their
    config, weights travel separately as a PyTreeParam)."""

    def __init__(self, module, method=None):
        self.module = module
        self.method = method
        self.int_input = bool(getattr(module, "int_input", False))

    def __call__(self, weights, inputs: Dict[str, jnp.ndarray]):
        args = list(inputs.values())
        variables = weights if (isinstance(weights, dict)
                                and "params" in weights) else {"params": weights}
        if self.method is not None:
            return self.module.apply(variables, *args, method=self.method)
        return self.module.apply(variables, *args)
