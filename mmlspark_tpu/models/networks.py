"""Flax model zoo — the network families the reference trains/serves.

TPU-native replacement for the reference's CNTK graphs: the BrainScript
ConvNet the cntk-train notebooks build (ref: notebooks/gpu/401 BrainScript
cell; src/cntk-train/.../BrainscriptBuilder.scala:16-120), the ResNet used
for CIFAR inference (ref: notebooks 301), ImageFeaturizer backbones
(ref: src/image-featurizer), and the Bi-LSTM entity extractor
(ref: notebook 304).

All modules are standard flax.linen, NHWC layouts, bfloat16-friendly:
``dtype`` controls compute precision while params stay float32 (the
canonical TPU mixed-precision recipe — MXU eats bf16, accumulates f32).

Every module exposes ``feature_layers()`` naming its intermediate
activation points so ImageFeaturizer-style layer cutting
(ref: ImageFeaturizer.scala:91-141 cutOutputLayers/layerNames) works on
any zoo model: pass ``capture=<name>`` to ``__call__`` and the module
returns that intermediate instead of the head output.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

Dtype = Any


class MLP(nn.Module):
    """Plain MLP over flat feature vectors."""

    features: Sequence[int] = (256, 128)
    num_classes: int = 10
    dtype: Dtype = jnp.float32
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False, capture: Optional[str] = None):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
            if self.dropout > 0:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
            if capture == f"dense_{i}":
                return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)

    def feature_layers(self) -> List[str]:
        return [f"dense_{i}" for i in range(len(self.features))]


class ConvNet(nn.Module):
    """The CIFAR ConvNet family of the cntk-train notebooks: stacked
    conv-relu(-pool) blocks then dense layers (ref: notebooks/gpu/401
    BrainScript ConvNet 32:32:3)."""

    conv_features: Sequence[int] = (64, 64, 64)
    kernel: Tuple[int, int] = (3, 3)
    pool_every: int = 1
    dense_features: Sequence[int] = (256,)
    num_classes: int = 10
    dtype: Dtype = jnp.float32
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False, capture: Optional[str] = None):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.conv_features):
            x = nn.Conv(f, self.kernel, dtype=self.dtype, name=f"conv_{i}")(x)
            x = nn.relu(x)
            if (i + 1) % self.pool_every == 0:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            if capture == f"conv_{i}":
                return x
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.dense_features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
            if self.dropout > 0:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
            if capture == f"dense_{i}":
                return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)

    def feature_layers(self) -> List[str]:
        return ([f"conv_{i}" for i in range(len(self.conv_features))]
                + [f"dense_{i}" for i in range(len(self.dense_features))])


class ResNetBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        # explicit symmetric (1,1) padding: identical to SAME at stride 1,
        # and matches torch's padding=1 at stride 2 (XLA SAME would pad
        # asymmetrically there), so imported torch checkpoints
        # (importers/torch_import.py) reproduce bit-comparable activations.
        # NOTE: stride-2 numerics differ from pre-torch-compat builds;
        # ResNet checkpoints saved before this change shift one pixel at
        # stage entries and should be retrained or re-imported
        pad = ((1, 1), (1, 1))
        residual = x
        y = nn.Conv(self.features, (3, 3), self.strides, padding=pad,
                    use_bias=False, dtype=self.dtype)(x)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding=pad, use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj")(residual)
            residual = nn.BatchNorm(use_running_average=not train,
                                    dtype=self.dtype)(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet family. ``stem='cifar'`` (default) is the CIFAR 6n+2
    style: 3x3 stem, stage_sizes=(3,3,3) -> ResNet-20.
    ``stem='imagenet'`` reproduces the torchvision ImageNet layout
    bit-for-bit (7x7/stride-2/pad-3 stem + BatchNorm + 3x3/stride-2
    maxpool with pad 1; stage_sizes=(2,2,2,2), width=64,
    num_classes=1000 -> torchvision resnet18) so published torchvision
    BasicBlock checkpoints import losslessly
    (importers/torch_import.py; ref: ModelDownloader.scala:209 — the
    reference's zoo is anchored on real published CNNs)."""

    stage_sizes: Sequence[int] = (3, 3, 3)
    width: int = 16
    num_classes: int = 10
    stem: str = "cifar"      # 'cifar' | 'imagenet'
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, capture: Optional[str] = None):
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            # torchvision: Conv2d(7, stride 2, padding 3) -> BN -> ReLU
            # -> MaxPool2d(3, stride 2, padding 1), with -inf padding so
            # the pooled border matches torch exactly
            x = nn.Conv(self.width, (7, 7), (2, 2),
                        padding=((3, 3), (3, 3)), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
            x = nn.BatchNorm(use_running_average=not train,
                             dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=((1, 1), (1, 1)))
        else:
            x = nn.Conv(self.width, (3, 3), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
            x = nn.BatchNorm(use_running_average=not train,
                             dtype=self.dtype)(x)
            x = nn.relu(x)
        for s, n_blocks in enumerate(self.stage_sizes):
            for b in range(n_blocks):
                strides = (2, 2) if (s > 0 and b == 0) else (1, 1)
                x = ResNetBlock(self.width * (2 ** s), strides,
                                self.dtype, name=f"stage{s}_block{b}")(
                                    x, train=train)
            if capture == f"stage{s}":
                return x
        x = jnp.mean(x, axis=(1, 2))
        if capture == "pool":
            return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)

    def feature_layers(self) -> List[str]:
        return [f"stage{s}" for s in range(len(self.stage_sizes))] + ["pool"]

    def numerics_markers(self) -> Dict[str, str]:
        """Saved-stage numerics versioning (core/serialize.py hook):
        checkpoints from before the explicit-(1,1)-padding change shift
        one pixel at stride-2 stage entries — loading them must warn."""
        return {"resnet_padding": "explicit11-torch-compat"}


class BiLSTMTagger(nn.Module):
    """Bidirectional LSTM sequence tagger — the TPU twin of the notebook
    304 Bi-LSTM medical-entity extractor (Keras/CNTK backend there).

    Input: int32 token ids [B, T]; output: per-token class logits
    [B, T, num_tags]. Uses nn.RNN over LSTMCells; the backward pass uses
    ``reverse=True`` with masking-friendly fixed-length scan, which XLA
    compiles to a single fused loop on TPU.
    """

    int_input = True  # consumes token ids, not float features

    vocab_size: int = 10000
    embed_dim: int = 128
    hidden: int = 128
    num_tags: int = 8
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False,
                 capture: Optional[str] = None):
        emb = nn.Embed(self.vocab_size, self.embed_dim,
                       dtype=self.dtype, name="embed")(tokens)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden), name="lstm_fwd")
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden), reverse=True,
                     keep_order=True, name="lstm_bwd")
        h = jnp.concatenate([fwd(emb), bwd(emb)], axis=-1)
        if capture == "lstm":
            return h
        return nn.Dense(self.num_tags, dtype=jnp.float32, name="head")(h)

    def feature_layers(self) -> List[str]:
        return ["lstm"]


class TransformerBlock(nn.Module):
    """Pre-LN decoder block; attention is pluggable so the same weights
    run dense (single chip) or ring/Ulysses (seq-sharded under
    shard_map via ``seq_axis``)."""

    dim: int
    heads: int
    mlp_ratio: int = 4
    causal: bool = True
    seq_axis: Optional[str] = None
    seq_impl: str = "ring"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        from mmlspark_tpu.parallel import ring_attention as ra
        b, l, _ = x.shape
        h = self.heads
        hd = self.dim // h
        y = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, h, hd)
        k = k.reshape(b, l, h, hd)
        v = v.reshape(b, l, h, hd)
        if self.seq_axis is not None:
            fn = (ra.ring_attention if self.seq_impl == "ring"
                  else ra.ulysses_attention)
            attn = fn(q, k, v, axis_name=self.seq_axis, causal=self.causal)
        else:
            attn = ra.attention(q, k, v, causal=self.causal)
        attn = attn.reshape(b, l, self.dim)
        x = x + nn.Dense(self.dim, dtype=self.dtype, name="proj")(attn)
        y = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        y = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype,
                     name="mlp_up")(y)
        y = nn.gelu(y)
        x = x + nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(y)
        return x


class Transformer(nn.Module):
    """Decoder-only transformer LM / sequence classifier.

    Long-context first-class: set ``seq_axis`` and apply under shard_map
    with the sequence dimension sharded on that mesh axis — attention
    runs as ring (ppermute) or Ulysses (all_to_all) collectives and the
    positional embedding uses each shard's global offset.
    """

    int_input = True  # consumes token ids, not float features

    vocab_size: int = 32000
    dim: int = 256
    depth: int = 4
    heads: int = 8
    max_len: int = 2048
    num_classes: int = 0     # 0 -> LM head over vocab
    causal: bool = True
    seq_axis: Optional[str] = None
    seq_impl: str = "ring"
    dtype: Dtype = jnp.float32
    # the vocab projection is the single largest matmul in an LM; f32
    # (default, conservative) runs it off the MXU's fast path, bf16 keeps
    # it on (losses still softmax in f32 — learner casts logits up)
    head_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False,
                 capture: Optional[str] = None):
        from jax import lax as _lax
        b, l = tokens.shape
        x = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                     name="embed")(tokens)
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_len, self.dim))
        if self.seq_axis is not None:
            n_shards = _lax.psum(1, self.seq_axis)  # static under shard_map
            if n_shards * l > self.max_len:
                raise ValueError(
                    f"global sequence {n_shards * l} exceeds "
                    f"max_len={self.max_len} (dynamic_slice would "
                    f"silently clamp positional embeddings)")
            offset = _lax.axis_index(self.seq_axis) * l
            pos = _lax.dynamic_slice_in_dim(pos_table, offset, l, axis=0)
        else:
            if l > self.max_len:
                raise ValueError(
                    f"sequence {l} exceeds max_len={self.max_len}")
            pos = pos_table[:l]
        x = x + pos[None].astype(self.dtype)
        for i in range(self.depth):
            x = TransformerBlock(
                self.dim, self.heads, causal=self.causal,
                seq_axis=self.seq_axis, seq_impl=self.seq_impl,
                dtype=self.dtype, name=f"block_{i}")(x)
            if capture == f"block_{i}":
                return x
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        if capture == "final":
            return x
        if self.num_classes > 0:
            # classify from the mean token representation
            pooled = jnp.mean(x, axis=1)
            if self.seq_axis is not None:
                pooled = _lax.pmean(pooled, self.seq_axis)
            return nn.Dense(self.num_classes, dtype=self.head_dtype,
                            name="head")(pooled)
        return nn.Dense(self.vocab_size, dtype=self.head_dtype,
                        name="lm_head")(x)

    def feature_layers(self) -> List[str]:
        return [f"block_{i}" for i in range(self.depth)] + ["final"]


# ---------------------------------------------------------------------------
# registry + spec construction (BrainScriptBuilder analog)
# ---------------------------------------------------------------------------

NETWORK_REGISTRY: Dict[str, Callable[..., nn.Module]] = {
    "mlp": MLP,
    "convnet": ConvNet,
    "resnet": ResNet,
    "bilstm": BiLSTMTagger,
    "transformer": Transformer,
}


def build_network(spec: Dict[str, Any]) -> nn.Module:
    """Build a module from a JSON-able spec — the declarative network
    definition layer replacing BrainScript emission
    (ref: BrainscriptBuilder.scala:16-120). Example::

        {"type": "resnet", "stage_sizes": [3,3,3], "num_classes": 10,
         "dtype": "bfloat16"}
    """
    spec = dict(spec)
    kind = spec.pop("type")
    if kind not in NETWORK_REGISTRY:
        raise KeyError(f"unknown network type {kind!r}; "
                       f"have {sorted(NETWORK_REGISTRY)}")
    for key in ("dtype", "head_dtype"):
        if key in spec and isinstance(spec[key], str):
            spec[key] = jnp.dtype(spec[key])
    for key in ("conv_features", "dense_features", "stage_sizes",
                "features", "kernel"):
        if key in spec and isinstance(spec[key], list):
            spec[key] = tuple(spec[key])
    return NETWORK_REGISTRY[kind](**spec)
