"""Linear models on JAX — logistic & linear regression estimators.

The reference's AutoML layer wraps SparkML's LogisticRegression /
LinearRegression as candidate models (ref: src/train-classifier/.../
TrainClassifier.scala:112-156 model-type heuristics). The TPU twin
implements them directly: full-batch gradient descent with Nesterov
momentum, the whole optimization loop one jitted ``lax.fori_loop`` —
static shapes, no host round-trips per step, MXU matmuls.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from mmlspark_tpu.core.params import (
    EnumParam, FloatParam, HasFeaturesCol, HasLabelCol, HasPredictionCol,
    IntParam, PyTreeParam, range_domain,
)
from mmlspark_tpu.core.schema import Field, Schema, F64, VECTOR
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.core.table import DataTable, features_matrix as _features_matrix


def _momentum_fit(loss_fn, init_params, lr, n_steps: int):
    """Shared full-batch momentum-GD loop (one jitted fori_loop) used by
    every linear model — dense and sparse paths optimize identically."""
    def body(i, carry):
        params, vel = carry
        g = jax.grad(loss_fn)(params)
        vel = jax.tree_util.tree_map(lambda vv, gg: 0.9 * vv - lr * gg,
                                     vel, g)
        params = jax.tree_util.tree_map(lambda p, vv: p + vv, params, vel)
        return params, vel

    zero_vel = jax.tree_util.tree_map(jnp.zeros_like, init_params)
    params, _ = lax.fori_loop(0, n_steps, body, (init_params, zero_vel))
    return params


def _fit_logistic(X, y, lr, l2, n_steps: int, num_class: int):
    """Cold-start fit = the warm-start kernel from zero inits (ONE
    definition of the loss/momentum loop, so fit and partial_fit can
    never silently diverge)."""
    d = X.shape[1]
    return _fit_logistic_warm(
        X, y, jnp.zeros((d, num_class)), jnp.zeros(num_class),
        lr, l2, n_steps, num_class)


@partial(jax.jit, static_argnames=("n_steps", "num_class", "d"))
def _fit_logistic_sparse(idx, val, y, lr, l2, n_steps: int,
                         num_class: int, d: int):
    """Sparse logistic regression: features arrive as padded (N, max_nnz)
    ``idx``/``val`` gather batches (CSRMatrix.padded_batch) and the
    matmul is W[idx] * val — embedding-style, so a 262144-wide hashed
    text matrix (ref: Featurize.scala:13-19) trains without a dense
    (N, D) activation ever existing. Autodiff turns the gather into the
    scatter-add gradient automatically. Padding entries (idx 0, val 0)
    contribute nothing."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class)

    def loss_fn(p):
        rows = p["W"][idx]                                  # (N, m, K)
        logits = jnp.einsum("nm,nmk->nk", val, rows) + p["b"]
        logp = jax.nn.log_softmax(logits)
        return (-jnp.mean(jnp.sum(onehot * logp, axis=1))
                + l2 * jnp.sum(p["W"] ** 2))

    return _momentum_fit(
        loss_fn, {"W": jnp.zeros((d, num_class)),
                  "b": jnp.zeros(num_class)}, lr, n_steps)


def _sparse_logits(csr, W: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Host-side CSR @ W + b without densifying (inference path)."""
    n = csr.shape[0]
    rows = np.repeat(np.arange(n), np.diff(csr.indptr).astype(np.int64))
    logits = np.zeros((n, W.shape[1]), np.float64)
    np.add.at(logits, rows, W[csr.indices] * csr.data[:, None])
    return logits + b


# ---------------------------------------------------------------------------
# device-batched trials (TuneHyperparameters' vmap CV path)
# ---------------------------------------------------------------------------

# one increment per jit TRACE of a batched-trial program — the
# zero-retrace guard for repeated CV sweeps at the same shapes
# (the linear-model analog of gbdt.booster.trace_counts)
_TRIAL_TRACES: dict = {"logistic_batch": 0, "linear_batch": 0}


def trial_trace_counts() -> dict:
    """Snapshot of batched-trial trace counters (tests/bench)."""
    return dict(_TRIAL_TRACES)


@partial(jax.jit, static_argnames=("n_steps", "num_class"))
def _fit_logistic_batch(X, y, lrs, l2s, n_steps: int, num_class: int):
    """C logistic trials on ONE (train-fold) matrix in one dispatch:
    vmap over the (lr, l2) candidate vectors, sharing X/y/onehot. The
    per-candidate program is exactly ``_fit_logistic``'s (same loss,
    same momentum loop), so a candidate's weights match its serial fit
    up to XLA's batched-op scheduling."""
    _TRIAL_TRACES["logistic_batch"] += 1   # trace-time side effect
    n, d = X.shape
    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class)

    def fit_one(lr, l2):
        def loss_fn(params):
            logits = X @ params["W"] + params["b"]
            logp = jax.nn.log_softmax(logits)
            return (-jnp.mean(jnp.sum(onehot * logp, axis=1))
                    + l2 * jnp.sum(params["W"] ** 2))

        return _momentum_fit(
            loss_fn, {"W": jnp.zeros((d, num_class)),
                      "b": jnp.zeros(num_class)}, lr, n_steps)

    return jax.vmap(fit_one)(lrs, l2s)


@partial(jax.jit, static_argnames=("n_steps",))
def _fit_linear_batch(X, y, lrs, l2s, n_steps: int):
    """C linear-regression trials in one dispatch (see
    ``_fit_logistic_batch``)."""
    _TRIAL_TRACES["linear_batch"] += 1   # trace-time side effect
    n, d = X.shape

    def fit_one(lr, l2):
        def loss_fn(p):
            pred = X @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2) + l2 * jnp.sum(p["w"] ** 2)

        return _momentum_fit(
            loss_fn, {"w": jnp.zeros(d), "b": jnp.asarray(0.0)},
            lr, n_steps)

    return jax.vmap(fit_one)(lrs, l2s)


def _fit_linear(X, y, lr, l2, n_steps: int):
    """Cold-start fit = the warm-start kernel from zero inits (see
    ``_fit_logistic``)."""
    return _fit_linear_warm(X, y, jnp.zeros(X.shape[1]),
                            jnp.asarray(0.0), lr, l2, n_steps)


# ---------------------------------------------------------------------------
# warm-started incremental updates (partial_fit — the online-refresh path)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_steps", "num_class"))
def _fit_logistic_warm(X, y, W0, b0, lr, l2, n_steps: int,
                       num_class: int):
    """``_fit_logistic`` initialized from existing weights instead of
    zeros: the SAME loss and momentum loop (velocity restarts at zero —
    the standard warm-start contract), so an incremental update is one
    jitted dispatch and a partial_fit stream stays deterministic."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class)

    def loss_fn(params):
        logits = X @ params["W"] + params["b"]
        logp = jax.nn.log_softmax(logits)
        return (-jnp.mean(jnp.sum(onehot * logp, axis=1))
                + l2 * jnp.sum(params["W"] ** 2))

    return _momentum_fit(loss_fn, {"W": W0, "b": b0}, lr, n_steps)


@partial(jax.jit, static_argnames=("n_steps",))
def _fit_linear_warm(X, y, w0, b0, lr, l2, n_steps: int):
    """``_fit_linear`` warm-started from existing weights (see
    ``_fit_logistic_warm``)."""
    def loss_fn(p):
        pred = X @ p["w"] + p["b"]
        return jnp.mean((pred - y) ** 2) + l2 * jnp.sum(p["w"] ** 2)

    return _momentum_fit(loss_fn, {"w": w0, "b": b0}, lr, n_steps)


class _Standardizer:
    """Feature standardization folded into the fitted params."""

    @staticmethod
    def compute(X: np.ndarray):
        mu = X.mean(axis=0)
        sd = X.std(axis=0)
        sd = np.where(sd < 1e-12, 1.0, sd)
        return mu, sd


class TPULogisticRegression(Estimator, HasFeaturesCol, HasLabelCol,
                            HasPredictionCol):
    """Multinomial logistic regression; labels must be 0..K-1.

    Standardization depends on the feature column's storage: DENSE
    features are standardized (mean/std folded into the fitted params);
    SPARSE (CSR) features are NOT — centering would densify, so the raw
    values feed the solver directly (the reference's hashed-text
    pipeline behaves the same). The same data therefore trains to a
    different model dense vs sparse at identical stepSize/regParam;
    pre-scale sparse features if scale-invariance matters."""

    maxIter = IntParam("gradient steps", default=300)
    regParam = FloatParam("L2 regularization", default=1e-4)
    stepSize = FloatParam("learning rate (dense features are "
                          "standardized first; sparse are not — see "
                          "class docstring)", default=0.5)

    def reads_columns(self, schema):
        return [self.get_features_col(), self.get_label_col()]

    def writes_columns(self, schema):
        return ["rawPrediction", "probability",
                self.get_prediction_col()]

    def fit(self, table: DataTable) -> "TPULogisticRegressionModel":
        from mmlspark_tpu.core.sparse import CSRMatrix
        y = np.asarray(table[self.get_label_col()], dtype=np.float64)
        num_class = int(y.max()) + 1 if len(y) else 2
        num_class = max(num_class, 2)
        feats = table.column(self.get_features_col())
        if isinstance(feats, CSRMatrix):
            # sparse path: no standardization (it would densify — the
            # reference's hashed-text pipeline does the same), gather
            # batches instead of a dense matrix
            max_nnz = max(1, feats.max_row_nnz())
            idx, val, _ = feats.padded_batch(0, len(feats), max_nnz)
            params = _fit_logistic_sparse(
                jnp.asarray(idx), jnp.asarray(val),
                jnp.asarray(y, jnp.float32),
                self.get("stepSize"), self.get("regParam"),
                self.get("maxIter"), num_class, feats.shape[1])
            weights = {"W": np.asarray(params["W"]),
                       "b": np.asarray(params["b"])}
        else:
            X = _features_matrix(table, self.get_features_col())
            mu, sd = _Standardizer.compute(X)
            Xs = (X - mu) / sd
            params = _fit_logistic(
                jnp.asarray(Xs, jnp.float32), jnp.asarray(y, jnp.float32),
                self.get("stepSize"), self.get("regParam"),
                self.get("maxIter"), num_class)
            weights = {"W": np.asarray(params["W"]),
                       "b": np.asarray(params["b"]),
                       "mu": mu, "sd": sd}
        model = TPULogisticRegressionModel(weights=weights)
        model.set("featuresCol", self.get_features_col())
        model.set("predictionCol", self.get_prediction_col())
        return model

    def partial_fit(self, table: DataTable,
                    model: Optional["TPULogisticRegressionModel"] = None,
                    ) -> "TPULogisticRegressionModel":
        """Incremental refresh: warm-start from ``model``'s weights and
        run ``maxIter`` momentum steps on this batch only — one jitted
        dispatch, no refit over history. ``model=None`` degenerates to
        ``fit``.

        The fit-time feature standardization (mu/sd) is FROZEN at the
        first fit: new batches standardize with the original stats, so
        the weight space stays consistent across updates (feature drift
        is surfaced by ``core.metrics.DriftMonitor``, not silently
        absorbed into shifting normalization). Deterministic: the same
        (model, batch) always produces the same new model, and the
        class count is pinned by the warm-started weight shape — labels
        outside it are an error, not a silent resize."""
        if model is None:
            return self.fit(table)
        from mmlspark_tpu.core.sparse import CSRMatrix
        w = model.get("weights")
        if "mu" not in w:
            raise ValueError(
                "partial_fit warm start requires a dense-featured model "
                "(sparse models carry no frozen standardization stats)")
        feats = table.column(self.get_features_col())
        if isinstance(feats, CSRMatrix):
            raise ValueError(
                "partial_fit requires dense features (the warm-started "
                "kernel standardizes against the frozen fit-time stats)")
        y = np.asarray(table[self.get_label_col()], dtype=np.float64)
        if len(y) == 0:
            # an empty refresh window is a no-op, not an update: zero
            # rows would mean() to NaN and silently corrupt the weights
            return model
        num_class = int(np.asarray(w["W"]).shape[1])
        if int(y.max()) + 1 > num_class:
            raise ValueError(
                f"label {int(y.max())} outside the warm-started model's "
                f"{num_class} classes; refit from scratch to add classes")
        X = _features_matrix(table, self.get_features_col())
        Xs = (X - w["mu"]) / w["sd"]
        params = _fit_logistic_warm(
            jnp.asarray(Xs, jnp.float32), jnp.asarray(y, jnp.float32),
            jnp.asarray(w["W"], jnp.float32),
            jnp.asarray(w["b"], jnp.float32),
            self.get("stepSize"), self.get("regParam"),
            self.get("maxIter"), num_class)
        out = TPULogisticRegressionModel(
            weights={"W": np.asarray(params["W"]),
                     "b": np.asarray(params["b"]),
                     "mu": w["mu"], "sd": w["sd"]})
        out.set("featuresCol", self.get_features_col())
        out.set("predictionCol", self.get_prediction_col())
        return out


class TPULogisticRegressionModel(Model, HasFeaturesCol, HasPredictionCol):
    weights = PyTreeParam("W/b/mu/sd arrays", default=None)
    # 'int8' models carry per-channel-quantized W (wq/w_scale) and a
    # calibrated per-tensor activation scale (x_scale) next to the f32
    # arrays; set by quantize(), surfaced as the serving precision label
    precision = EnumParam(["f32", "int8"],
                          "inference precision (set by quantize())",
                          default="f32")

    def reads_columns(self, schema):
        return [self.get_features_col()]

    def writes_columns(self, schema):
        return ["rawPrediction", "probability",
                self.get_prediction_col()]

    def quantize(self, calib: DataTable, percentile: float = 100.0
                 ) -> "TPULogisticRegressionModel":
        """Int8 post-training quantization (core/quantize.py): W gets
        per-class-channel symmetric scales, the standardized feature
        activations get a per-tensor clip calibrated on ``calib``'s
        feature rows, and the returned NEW model scores through an
        int8xint8->i32 matmul with an f32 dequant epilogue on both the
        host and the fused device path. This (f32) model is untouched —
        the accuracy oracle and swap-rollback target."""
        from mmlspark_tpu.core import quantize as QZ
        w = self.get("weights")
        if w is None or "mu" not in w:
            raise ValueError(
                "quantize requires a dense-featured model (sparse models "
                "score through the host CSR path and carry no "
                "standardization stats to calibrate against)")
        table = calib if isinstance(calib, DataTable) \
            else DataTable(dict(calib))
        X = _features_matrix(table, self.get_features_col())
        if X.shape[0] == 0:
            raise ValueError("quantize needs at least one calibration row")
        Xs = (X - w["mu"]) / w["sd"]
        wq, w_scale = QZ.quantize_weight(np.asarray(w["W"]), axis=-1)
        cal = QZ.ActivationCalibrator(percentile=percentile)
        cal.observe("x", Xs)
        qweights = {k: np.asarray(v) for k, v in w.items()}
        qweights.update(wq=wq, w_scale=w_scale, x_scale=cal.scale("x"))
        out = TPULogisticRegressionModel(weights=qweights,
                                         precision="int8")
        out.set("featuresCol", self.get_features_col())
        out.set("predictionCol", self.get_prediction_col())
        return out

    def device_op(self, schema):
        """Fusion hook (core/fusion.py): standardize + logits + softmax
        + argmax as one pure-f32 device kernel. The host ``transform``
        computes the same formulas in float64 numpy, so fused
        predictions match exactly (argmax) and probabilities to f32
        rounding; ``transform_staged`` (the same kernel dispatched
        stage-at-a-time) is bit-identical."""
        from mmlspark_tpu.core import fusion as FZ
        from mmlspark_tpu.core import quantize as QZ
        w = self.get("weights")
        if w is None or "mu" not in w:
            return None    # sparse-featured models score on host
        feat = self.get_features_col()
        pred_col = self.get_prediction_col()
        binary = int(np.asarray(w["W"]).shape[1]) == 2
        int8 = self.get("precision") == "int8"

        def make_consts():
            ww = self.get("weights")
            consts = {"b": np.asarray(ww["b"], np.float32),
                      "mu": np.asarray(ww["mu"], np.float32),
                      "sd": np.asarray(ww["sd"], np.float32)}
            if int8:
                consts.update(
                    wq=np.asarray(ww["wq"], np.int8),
                    w_scale=np.asarray(ww["w_scale"], np.float32),
                    x_scale=np.float32(ww["x_scale"]))
            else:
                consts["W"] = np.asarray(ww["W"], np.float32)
            return consts

        def fn(consts, env, _f=feat, _p=pred_col, _bin=binary,
               _int8=int8):
            X = env[_f]
            Xs = (X - consts["mu"]) / consts["sd"]
            if _int8:
                # int8 MXU path + f32 dequant epilogue (no f64 anywhere
                # — the audited quantization contract)
                logits = QZ.int8_matmul(
                    Xs, consts["wq"], consts["x_scale"],
                    consts["w_scale"]) + consts["b"]
            else:
                logits = Xs @ consts["W"] + consts["b"]
            m = jnp.max(logits, axis=1, keepdims=True)
            e = jnp.exp(logits - m)
            prob = e / jnp.sum(e, axis=1, keepdims=True)
            pred = jnp.argmax(prob, axis=1).astype(jnp.float32)
            if _bin:
                raw = jnp.stack([logits[:, 0] - logits[:, 1],
                                 logits[:, 1] - logits[:, 0]], axis=1)
            else:
                raw = logits
            return {"rawPrediction": raw, "probability": prob, _p: pred}

        return FZ.DeviceOp(
            self, reads=[feat],
            writes=["rawPrediction", "probability", pred_col],
            fn=fn, make_consts=make_consts,
            out_fields={"rawPrediction": Field("rawPrediction", VECTOR),
                        "probability": Field("probability", VECTOR),
                        pred_col: Field(pred_col, F64)},
            out_dtypes={"rawPrediction": np.float64,
                        "probability": np.float64,
                        pred_col: np.float64},
            # :int8 suffix scopes the checker's no-f64-upcast audit to
            # quantized kernels (tools/check_fusion_kernels.py)
            name=(f"{type(self).__name__}:{self.uid}:int8"
                  if int8 else None))

    def drift_monitor(self):
        """A ``core.metrics.DriftMonitor`` seeded with this model's
        FIT-TIME feature statistics (mu/sd) — hand it to
        ``json_scoring_pipeline`` so served traffic's per-feature
        mean/var/null drift vs training shows up on /healthz."""
        from mmlspark_tpu.core.metrics import DriftMonitor
        w = self.get("weights")
        if "mu" not in w:
            raise ValueError("sparse-featured models carry no fit-time "
                             "standardization stats to drift against")
        return DriftMonitor(w["mu"], np.asarray(w["sd"]) ** 2)

    def transform(self, table: DataTable) -> DataTable:
        from mmlspark_tpu.core.sparse import CSRMatrix
        w = self.get("weights")
        feats = table.column(self.get_features_col())
        if isinstance(feats, CSRMatrix) and "mu" not in w:
            logits = _sparse_logits(feats, np.asarray(w["W"]),
                                    np.asarray(w["b"]))
            return self._attach_scores(table, logits)
        return self.transform_from_matrix(
            table, _features_matrix(table, self.get_features_col()))

    def transform_from_matrix(self, table: DataTable,
                              X: np.ndarray) -> DataTable:
        """``transform`` with the dense (N, D) extraction hoisted by the
        caller — the CV hot path scores every candidate against ONE
        cached fold matrix instead of re-extracting it per candidate."""
        from mmlspark_tpu.core.quantize import int8_matmul_host
        w = self.get("weights")
        if "mu" in w:
            X = (X - w["mu"]) / w["sd"]
        if self.get("precision") == "int8":
            # integer accumulation is exact, so the host path agrees
            # with the fused device kernel bit-for-bit on the i32
            # accumulator; the f32 dequant mirrors XLA's epilogue
            logits = int8_matmul_host(X, w["wq"], w["x_scale"],
                                      w["w_scale"]) + w["b"]
            return self._attach_scores(table, logits)
        return self._attach_scores(table, X @ w["W"] + w["b"])

    def _attach_scores(self, table: DataTable,
                       logits: np.ndarray) -> DataTable:
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        prob = e / e.sum(axis=1, keepdims=True)
        pred = prob.argmax(axis=1).astype(np.float64)
        if prob.shape[1] == 2:
            raw = np.stack([-logits[:, 1] + logits[:, 0],
                            logits[:, 1] - logits[:, 0]], axis=1)
        else:
            raw = logits
        return (table
                .with_column("rawPrediction", raw.astype(np.float64),
                             Field("rawPrediction", VECTOR))
                .with_column("probability", prob.astype(np.float64),
                             Field("probability", VECTOR))
                .with_column(self.get_prediction_col(), pred,
                             Field(self.get_prediction_col(), F64)))

    def transform_schema(self, schema: Schema) -> Schema:
        return (schema
                .add_or_replace(Field("rawPrediction", VECTOR))
                .add_or_replace(Field("probability", VECTOR))
                .add_or_replace(Field(self.get_prediction_col(), F64)))


class TPULinearRegression(Estimator, HasFeaturesCol, HasLabelCol,
                          HasPredictionCol):
    maxIter = IntParam("gradient steps", default=300)
    regParam = FloatParam("L2 regularization", default=1e-4)
    stepSize = FloatParam("learning rate", default=0.1)

    def reads_columns(self, schema):
        return [self.get_features_col(), self.get_label_col()]

    def writes_columns(self, schema):
        return [self.get_prediction_col()]

    def fit(self, table: DataTable) -> "TPULinearRegressionModel":
        X = _features_matrix(table, self.get_features_col())
        y = np.asarray(table[self.get_label_col()], dtype=np.float64)
        mu, sd = _Standardizer.compute(X)
        y_mu, y_sd = float(y.mean()), float(y.std() or 1.0)
        Xs = (X - mu) / sd
        ys = (y - y_mu) / y_sd
        params = _fit_linear(
            jnp.asarray(Xs, jnp.float32), jnp.asarray(ys, jnp.float32),
            self.get("stepSize"), self.get("regParam"), self.get("maxIter"))
        model = TPULinearRegressionModel(
            weights={"w": np.asarray(params["w"]),
                     "b": np.asarray(params["b"]),
                     "mu": mu, "sd": sd, "y_mu": y_mu, "y_sd": y_sd})
        model.set("featuresCol", self.get_features_col())
        model.set("predictionCol", self.get_prediction_col())
        return model

    def partial_fit(self, table: DataTable,
                    model: Optional["TPULinearRegressionModel"] = None,
                    ) -> "TPULinearRegressionModel":
        """Warm-started incremental update (see
        ``TPULogisticRegression.partial_fit``): feature AND label
        standardization stats are frozen at the first fit, the momentum
        loop restarts from the fitted weights on this batch only."""
        if model is None:
            return self.fit(table)
        w = model.get("weights")
        y = np.asarray(table[self.get_label_col()], dtype=np.float64)
        if len(y) == 0:
            return model   # empty refresh window: no-op (NaN guard)
        X = _features_matrix(table, self.get_features_col())
        Xs = (X - w["mu"]) / w["sd"]
        ys = (y - w["y_mu"]) / w["y_sd"]
        params = _fit_linear_warm(
            jnp.asarray(Xs, jnp.float32), jnp.asarray(ys, jnp.float32),
            jnp.asarray(w["w"], jnp.float32),
            jnp.asarray(w["b"], jnp.float32),
            self.get("stepSize"), self.get("regParam"),
            self.get("maxIter"))
        out = TPULinearRegressionModel(
            weights={"w": np.asarray(params["w"]),
                     "b": np.asarray(params["b"]),
                     "mu": w["mu"], "sd": w["sd"],
                     "y_mu": w["y_mu"], "y_sd": w["y_sd"]})
        out.set("featuresCol", self.get_features_col())
        out.set("predictionCol", self.get_prediction_col())
        return out


class TPULinearRegressionModel(Model, HasFeaturesCol, HasPredictionCol):
    weights = PyTreeParam("w/b/mu/sd arrays", default=None)
    precision = EnumParam(["f32", "int8"],
                          "inference precision (set by quantize())",
                          default="f32")

    def reads_columns(self, schema):
        return [self.get_features_col()]

    def writes_columns(self, schema):
        return [self.get_prediction_col()]

    def quantize(self, calib: DataTable, percentile: float = 100.0
                 ) -> "TPULinearRegressionModel":
        """Int8 PTQ of the regression weight vector (treated as a
        (D, 1) matmul — one output channel, one weight scale) with the
        standardized-feature activation clip calibrated on ``calib``.
        See ``TPULogisticRegressionModel.quantize``."""
        from mmlspark_tpu.core import quantize as QZ
        w = self.get("weights")
        if w is None:
            raise ValueError("quantize requires a fitted model "
                             "(weights is None)")
        table = calib if isinstance(calib, DataTable) \
            else DataTable(dict(calib))
        X = _features_matrix(table, self.get_features_col())
        if X.shape[0] == 0:
            raise ValueError("quantize needs at least one calibration row")
        Xs = (X - w["mu"]) / w["sd"]
        wq, w_scale = QZ.quantize_weight(
            np.asarray(w["w"]).reshape(-1, 1), axis=-1)
        cal = QZ.ActivationCalibrator(percentile=percentile)
        cal.observe("x", Xs)
        qweights = {k: np.asarray(v) for k, v in w.items()}
        qweights.update(wq=wq, w_scale=w_scale, x_scale=cal.scale("x"))
        out = TPULinearRegressionModel(weights=qweights,
                                       precision="int8")
        out.set("featuresCol", self.get_features_col())
        out.set("predictionCol", self.get_prediction_col())
        return out

    def device_op(self, schema):
        """Fusion hook: standardize + dot + un-standardize in f32 (see
        ``TPULogisticRegressionModel.device_op``); int8 models route the
        dot through the quantized matmul with its f32 dequant epilogue."""
        from mmlspark_tpu.core import fusion as FZ
        from mmlspark_tpu.core import quantize as QZ
        w = self.get("weights")
        if w is None:
            return None
        feat = self.get_features_col()
        pred_col = self.get_prediction_col()
        int8 = self.get("precision") == "int8"

        def make_consts():
            ww = self.get("weights")
            consts = {"b": np.asarray(ww["b"], np.float32),
                      "mu": np.asarray(ww["mu"], np.float32),
                      "sd": np.asarray(ww["sd"], np.float32),
                      "y_mu": np.float32(ww["y_mu"]),
                      "y_sd": np.float32(ww["y_sd"])}
            if int8:
                consts.update(
                    wq=np.asarray(ww["wq"], np.int8),
                    w_scale=np.asarray(ww["w_scale"], np.float32),
                    x_scale=np.float32(ww["x_scale"]))
            else:
                consts["w"] = np.asarray(ww["w"], np.float32)
            return consts

        def fn(consts, env, _f=feat, _p=pred_col, _int8=int8):
            Xs = (env[_f] - consts["mu"]) / consts["sd"]
            if _int8:
                dot = QZ.int8_matmul(Xs, consts["wq"],
                                     consts["x_scale"],
                                     consts["w_scale"])[:, 0]
            else:
                dot = Xs @ consts["w"]
            pred = (dot + consts["b"]) * consts["y_sd"] \
                + consts["y_mu"]
            return {_p: pred}

        return FZ.DeviceOp(
            self, reads=[feat], writes=[pred_col], fn=fn,
            make_consts=make_consts,
            out_fields={pred_col: Field(pred_col, F64)},
            out_dtypes={pred_col: np.float64},
            name=(f"{type(self).__name__}:{self.uid}:int8"
                  if int8 else None))

    def drift_monitor(self):
        """Fit-time feature-stat DriftMonitor (see
        ``TPULogisticRegressionModel.drift_monitor``)."""
        from mmlspark_tpu.core.metrics import DriftMonitor
        w = self.get("weights")
        return DriftMonitor(w["mu"], np.asarray(w["sd"]) ** 2)

    def transform(self, table: DataTable) -> DataTable:
        return self.transform_from_matrix(
            table, _features_matrix(table, self.get_features_col()))

    def transform_from_matrix(self, table: DataTable,
                              X: np.ndarray) -> DataTable:
        """``transform`` with the (N, D) extraction hoisted by the
        caller (see TPULogisticRegressionModel.transform_from_matrix)."""
        from mmlspark_tpu.core.quantize import int8_matmul_host
        w = self.get("weights")
        Xs = (X - w["mu"]) / w["sd"]
        if self.get("precision") == "int8":
            dot = int8_matmul_host(Xs, w["wq"], w["x_scale"],
                                   w["w_scale"])[:, 0]
        else:
            dot = Xs @ w["w"]
        pred = (dot + w["b"]) * w["y_sd"] + w["y_mu"]
        return table.with_column(self.get_prediction_col(),
                                 np.asarray(pred, dtype=np.float64),
                                 Field(self.get_prediction_col(), F64))

    def transform_schema(self, schema: Schema) -> Schema:
        return schema.add_or_replace(Field(self.get_prediction_col(), F64))
